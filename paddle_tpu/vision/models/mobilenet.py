"""MobileNetV1/V2 (python/paddle/vision/models/mobilenetv1.py / v2 analog)."""

from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _conv_bn(in_c, out_c, k, stride=1, padding=0, groups=1):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(out_c), nn.ReLU6())


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = _conv_bn(in_c, in_c, 3, stride, 1, groups=in_c)
        self.pw = _conv_bn(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        cfg = [(c(32), c(64), 1), (c(64), c(128), 2), (c(128), c(128), 1),
               (c(128), c(256), 2), (c(256), c(256), 1), (c(256), c(512), 2),
               *[(c(512), c(512), 1)] * 5,
               (c(512), c(1024), 2), (c(1024), c(1024), 1)]
        layers = [_conv_bn(3, c(32), 3, stride=2, padding=1)]
        layers += [_DepthwiseSeparable(i, o, s) for i, o, s in cfg]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(inp, hidden, 1))
        layers += [
            _conv_bn(hidden, hidden, 3, stride, 1, groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = max(8, int(32 * scale))
        last_c = max(8, int(1280 * scale))
        layers = [_conv_bn(3, in_c, 3, stride=2, padding=1)]
        for t, ch, n, s in cfg:
            out_c = max(8, int(ch * scale))
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c,
                                                s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_conv_bn(in_c, last_c, 1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    if pretrained:
        raise NotImplementedError("no network access for pretrained weights")
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    if pretrained:
        raise NotImplementedError("no network access for pretrained weights")
    return MobileNetV2(scale=scale, **kw)
