"""ShuffleNetV2 (python/paddle/vision/models/shufflenetv2.py analog).

Uses the schema-codegen'd channel_shuffle op (ops/schema_defs.py)."""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512), 0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024), 1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024), 2.0: (24, 244, 488, 976, 2048),
}


def _act(name):
    return nn.Hardswish() if name == "swish" else nn.ReLU()


def _conv_bn_act(in_c, out_c, k, stride, pad, groups=1, act="relu"):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=pad,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act is not None:
        layers.append(_act(act))
    return nn.Sequential(*layers)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn_act(branch_c, branch_c, 1, 1, 0, act=act),
                _conv_bn_act(branch_c, branch_c, 3, 1, 1, groups=branch_c,
                             act=None),
                _conv_bn_act(branch_c, branch_c, 1, 1, 0, act=act))
        else:
            self.branch1 = nn.Sequential(
                _conv_bn_act(in_c, in_c, 3, stride, 1, groups=in_c,
                             act=None),
                _conv_bn_act(in_c, branch_c, 1, 1, 0, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn_act(in_c, branch_c, 1, 1, 0, act=act),
                _conv_bn_act(branch_c, branch_c, 3, stride, 1,
                             groups=branch_c, act=None),
                _conv_bn_act(branch_c, branch_c, 1, 1, 0, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return F.channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        c0, c1, c2, c3, c_last = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _conv_bn_act(3, c0, 3, 2, 1, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = c0
        for out_c, repeat in zip((c1, c2, c3), (4, 8, 4)):
            stages.append(_InvertedResidual(in_c, out_c, 2, act))
            for _ in range(repeat - 1):
                stages.append(_InvertedResidual(out_c, out_c, 1, act))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn_act(in_c, c_last, 1, 1, 0, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c_last, num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _make(scale, act="relu", name=""):
    def f(pretrained=False, **kwargs):
        if pretrained:
            raise NotImplementedError("pretrained weights: use paddle.hub")
        return ShuffleNetV2(scale=scale, act=act, **kwargs)

    f.__name__ = name
    return f


shufflenet_v2_x0_25 = _make(0.25, name="shufflenet_v2_x0_25")
shufflenet_v2_x0_33 = _make(0.33, name="shufflenet_v2_x0_33")
shufflenet_v2_x0_5 = _make(0.5, name="shufflenet_v2_x0_5")
shufflenet_v2_x1_0 = _make(1.0, name="shufflenet_v2_x1_0")
shufflenet_v2_x1_5 = _make(1.5, name="shufflenet_v2_x1_5")
shufflenet_v2_x2_0 = _make(2.0, name="shufflenet_v2_x2_0")
shufflenet_v2_swish = _make(1.0, act="swish", name="shufflenet_v2_swish")
