"""MobileNetV3 Small/Large (python/paddle/vision/models/mobilenetv3.py
analog)."""

from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SE(nn.Layer):
    def __init__(self, c, squeeze=4):
        super().__init__()
        mid = _make_divisible(c // squeeze)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(c, mid, 1)
        self.fc2 = nn.Conv2D(mid, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp != in_c:
            layers += [nn.Conv2D(in_c, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), act_layer()]
        layers += [nn.Conv2D(exp, exp, k, stride=stride, padding=k // 2,
                             groups=exp, bias_attr=False),
                   nn.BatchNorm2D(exp)]
        if se:
            layers.append(_SE(exp))
        layers += [act_layer(),
                   nn.Conv2D(exp, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_SMALL = [  # k, exp, out, se, act, stride
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]
_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, in_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_c), nn.Hardswish())
        blocks = []
        for k, exp, out_c, se, act, stride in cfg:
            exp_c = _make_divisible(exp * scale)
            o = _make_divisible(out_c * scale)
            blocks.append(_Block(in_c, exp_c, o, k, stride, se, act))
            in_c = o
        self.blocks = nn.Sequential(*blocks)
        last_c = _make_divisible(last_exp * scale)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, last_c, 1, bias_attr=False),
            nn.BatchNorm2D(last_c), nn.Hardswish())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            head_c = 1024 if last_exp == 576 else 1280
            self.classifier = nn.Sequential(
                nn.Linear(last_c, head_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(head_c, num_classes))

    def forward(self, x):
        x = self.conv_last(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, last_exp=576, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, last_exp=960, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights: use paddle.hub")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights: use paddle.hub")
    return MobileNetV3Large(scale=scale, **kwargs)
