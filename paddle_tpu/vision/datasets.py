"""Vision datasets (python/paddle/vision/datasets/ analog).

No network egress in this environment, so MNIST/Cifar load from local
files when present (same on-disk formats as the reference) and raise a
clear error otherwise; FakeData provides deterministic synthetic images
for tests/benchmarks (the reference's approach of faking data sources in
CI, SURVEY §4e)."""

from __future__ import annotations

import gzip
import os
import pickle
import tarfile
from typing import Callable, Optional

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, size=128, image_shape=(3, 32, 32), num_classes=10,
                 transform: Optional[Callable] = None):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.default_rng(idx)
        img = rng.standard_normal(self.image_shape).astype(np.float32)
        label = int(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


class MNIST(Dataset):
    """idx-format MNIST (reference: vision/datasets/mnist.py), local files
    only: pass image_path/label_path to the raw gz files."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download:
            raise RuntimeError("no network egress; place MNIST idx files "
                               "locally and pass image_path/label_path")
        if image_path is None or label_path is None:
            raise ValueError("MNIST requires local image_path and label_path")
        self.transform = transform
        with gzip.open(image_path, "rb") as f:
            data = f.read()
        n = int.from_bytes(data[4:8], "big")
        rows = int.from_bytes(data[8:12], "big")
        cols = int.from_bytes(data[12:16], "big")
        self.images = np.frombuffer(data, np.uint8, offset=16).reshape(
            n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            ldata = f.read()
        self.labels = np.frombuffer(ldata, np.uint8, offset=8).astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """python-pickle CIFAR tarball (reference: vision/datasets/cifar.py),
    local file only."""

    N_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download:
            raise RuntimeError("no network egress; pass a local data_file")
        if data_file is None:
            raise ValueError("Cifar10 requires a local data_file tar.gz")
        self.transform = transform
        want = "test_batch" if mode == "test" else "data_batch"
        if self.N_CLASSES == 100:
            want = "test" if mode == "test" else "train"
        images, labels = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            for member in tf.getmembers():
                if want in member.name:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    images.append(d[b"data"])
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    labels.extend(d[key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    N_CLASSES = 100
