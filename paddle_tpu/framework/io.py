"""paddle.save / paddle.load analog (python/paddle/framework/io.py:725,:967).

Pickle-based nested state_dict serialization with Tensor -> numpy conversion;
directories are created on demand; >4GB handled by pickle protocol 4.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from paddle_tpu.framework.tensor import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


class _TensorPayload:
    """Marker wrapper so load() can re-wrap arrays as Tensors."""

    __slots__ = ("array",)

    def __init__(self, array: np.ndarray):
        self.array = array


def _to_saveable(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        a = np.asarray(obj.value)
        return _TensorPayload(a)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saved(obj: Any) -> Any:
    if isinstance(obj, _TensorPayload):
        return Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _from_saved(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saved(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)
    os.replace(tmp, path)  # atomic _safe_save analog (io_utils.py)


def load(path: str, return_numpy: bool = False) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        def unwrap(o):
            if isinstance(o, _TensorPayload):
                return o.array
            if isinstance(o, dict):
                return {k: unwrap(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return type(o)(unwrap(v) for v in o)
            return o
        return unwrap(obj)
    return _from_saved(obj)
