"""Version shims over the jax surface this framework targets.

The codebase is written against the current jax API; these shims keep it
importable on the previous LTS line where a few symbols live elsewhere:

- ``jax.shard_map`` (function) was ``jax.experimental.shard_map.shard_map``
  with ``check_rep`` instead of ``check_vma``;
- ``jax.experimental.pallas.tpu.CompilerParams`` was ``TPUCompilerParams``;
- ``jax.core.get_opaque_trace_state`` gained a required (ignored)
  ``convention`` argument — see ``jit.cond_capture.opaque_trace_state``.

Every shim resolves at import time so call sites pay nothing per call.
"""

from __future__ import annotations

__all__ = ["shard_map", "pallas_tpu_compiler_params"]

try:
    from jax import shard_map as shard_map  # noqa: F401  (new home)
except ImportError:                          # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f=None, /, **kwargs):
        # translate the new spelling's check_vma= to the old check_rep=
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return lambda g: _shard_map(g, **kwargs)
        return _shard_map(f, **kwargs)


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` on current jax, ``TPUCompilerParams`` before
    the rename — construct whichever this jax provides."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)
