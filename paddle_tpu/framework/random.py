"""Global RNG state.

Analog of the reference's ``phi::Generator`` (paddle/phi/core/generator.h) and
``paddle.seed``/``get_rng_state``. JAX RNG is functional (explicit keys), so the
eager layer keeps a splittable global generator: every eager random op splits
one subkey off the global state. Jit-traced model code should thread keys
explicitly (our nn layers take/derive keys from this generator at init time,
which happens eagerly, so initialization is reproducible under `seed`).
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import numpy as np

__all__ = ["Generator", "seed", "default_generator", "get_rng_state", "set_rng_state", "split_key"]


def _tracing() -> bool:
    try:
        from jax._src import core as _core

        return not _core.trace_state_clean()
    except Exception:
        return False


class Generator:
    """Splittable PRNG stream backed by a jax.random key.

    Trace-safe: inside a jit trace, keys are derived by fold_in on a host
    counter and the stored key is NEVER replaced with a traced value (a
    traced key would poison every later trace — UnexpectedTracerError).
    Inside one compiled program the derived keys are constants, so repeated
    executions reuse the same stream; compiled training steps that need
    fresh randomness per step thread a traced key via push_trace_key
    (to_static and ShardedTrainer both do).
    """

    def __init__(self, seed_: int = 0):
        self._seed = seed_
        # lazy: materializing a key initializes the jax backend, and the
        # module-level default Generator must not pin the backend at import
        # time (multi-host jax.distributed.initialize comes after import)
        self._key_ = None
        self._draws = 0
        self._lock = threading.Lock()

    @property
    def _key(self):
        if self._key_ is None:
            self._key_ = jax.random.key(self._seed)
        return self._key_

    @_key.setter
    def _key(self, k):
        self._key_ = k

    def manual_seed(self, seed_: int) -> "Generator":
        with self._lock:
            self._seed = seed_
            self._key_ = None
            self._draws = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def split(self, num: int = 1):
        """Return `num` fresh subkeys, advancing the stream."""
        with self._lock:
            if _tracing():
                self._draws += 1
                base = jax.random.fold_in(self._key, self._draws)
                if num == 1:
                    return [base]
                return [jax.random.fold_in(base, i) for i in range(num)]
            keys = jax.random.split(self._key, num + 1)
            self._key = keys[0]
            self._draws = 0
            return list(keys[1:]) if num > 1 else [keys[1]]

    def get_state(self):
        return jax.random.key_data(self._key)

    def set_state(self, state) -> None:
        self._key = jax.random.wrap_key_data(np.asarray(state))


class _TraceKeyStack(threading.local):
    """When jit-tracing (to_static), random ops must draw from a *traced* key
    passed into the compiled function — otherwise the eager key would be baked
    in as a constant and every step would reuse the same dropout mask."""

    def __init__(self):
        self.stack: List = []


_trace_keys = _TraceKeyStack()


def push_trace_key(key) -> None:
    _trace_keys.stack.append(key)


def pop_trace_key() -> None:
    _trace_keys.stack.pop()


def in_trace() -> bool:
    return bool(_trace_keys.stack)


_default = Generator(0)


def default_generator() -> Generator:
    return _default


def seed(s: int) -> Generator:
    """paddle.seed analog: reset the global generator."""
    return _default.manual_seed(int(s))


def split_key(num: int = 1, generator: Optional[Generator] = None):
    if _trace_keys.stack:
        top = _trace_keys.stack[-1]
        keys = jax.random.split(top, num + 1)
        _trace_keys.stack[-1] = keys[0]
        return keys[1] if num == 1 else list(keys[1:])
    gen = generator or _default
    keys = gen.split(num)
    return keys[0] if num == 1 else keys


def get_rng_state():
    return _default.get_state()


def set_rng_state(state) -> None:
    _default.set_state(state)
