"""Data types for paddle_tpu tensors.

Analog of the reference's ``phi::DataType`` (paddle/phi/common/data_type.h) —
collapsed onto JAX/XLA dtypes. TPU-native note: bfloat16 is a first-class
training dtype (MXU-native); float64 exists for numerics tests only.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = [
    "dtype", "float16", "bfloat16", "float32", "float64",
    "int8", "int16", "int32", "int64", "uint8", "bool_",
    "complex64", "complex128", "convert_dtype", "is_floating_point_dtype",
    "is_integer_dtype", "finfo", "iinfo",
]

# Canonical dtype objects are jnp dtypes (numpy dtype instances).
float16 = jnp.dtype("float16")
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype("float32")
float64 = jnp.dtype("float64")
int8 = jnp.dtype("int8")
int16 = jnp.dtype("int16")
int32 = jnp.dtype("int32")
int64 = jnp.dtype("int64")
uint8 = jnp.dtype("uint8")
bool_ = jnp.dtype("bool")
complex64 = jnp.dtype("complex64")
complex128 = jnp.dtype("complex128")

dtype = np.dtype  # the type of a dtype object

_STR_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int": int32,
    "int64": int64, "long": int64, "uint8": uint8,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
}


def convert_dtype(d):
    """Normalize any dtype spec (str, np dtype, python type) to a jnp dtype."""
    if d is None:
        return None
    if isinstance(d, str):
        try:
            return _STR_ALIASES[d]
        except KeyError:
            raise ValueError(f"unknown dtype {d!r}")
    if d is float:
        return float32
    if d is int:
        return int64
    if d is bool:
        return bool_
    return jnp.dtype(d)


def is_floating_point_dtype(d) -> bool:
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.floating)


def is_integer_dtype(d) -> bool:
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.integer)


def finfo(d):
    return jnp.finfo(convert_dtype(d))


def iinfo(d):
    return jnp.iinfo(convert_dtype(d))
