"""Runtime counters + device memory statistics.

Analog of the reference's monitor registry
(paddle/fluid/platform/monitor.cc STAT_INT64 / StatRegistry) and the memory
stats API (paddle/fluid/memory/stats.h memory_allocated /
max_memory_allocated): host-side counters are a thread-safe registry;
device memory numbers come straight from the PJRT runtime
(``device.memory_stats()``) since XLA owns the allocator.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["stat_add", "stat_get", "stat_reset", "stat_values",
           "memory_allocated", "max_memory_allocated", "memory_reserved",
           "device_memory_stats"]


class _StatRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, int] = {}

    def add(self, name: str, value: int = 1) -> int:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + int(value)
            return self._stats[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._stats.get(name, 0)

    def reset(self, name: Optional[str] = None) -> None:
        with self._lock:
            if name is None:
                self._stats.clear()
            else:
                self._stats.pop(name, None)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)


_registry = _StatRegistry()

stat_add = _registry.add
stat_get = _registry.get
stat_reset = _registry.reset
stat_values = _registry.snapshot


def _device(device_id: Optional[int]):
    import jax
    devs = jax.local_devices()
    return devs[device_id or 0]


def device_memory_stats(device_id: Optional[int] = None) -> dict:
    """Raw PJRT memory stats dict ({} when the backend exposes none —
    notably the CPU backend)."""
    stats = _device(device_id).memory_stats()
    return dict(stats) if stats else {}


def _live_bytes_fallback() -> int:
    import jax
    return sum(v.nbytes for v in jax.live_arrays())


def memory_allocated(device_id: Optional[int] = None) -> int:
    """Bytes currently allocated on the device (memory/stats.h
    memory_allocated analog)."""
    s = device_memory_stats(device_id)
    if "bytes_in_use" in s:
        return int(s["bytes_in_use"])
    return _live_bytes_fallback()


def max_memory_allocated(device_id: Optional[int] = None) -> int:
    s = device_memory_stats(device_id)
    if "peak_bytes_in_use" in s:
        return int(s["peak_bytes_in_use"])
    return _live_bytes_fallback()


def memory_reserved(device_id: Optional[int] = None) -> int:
    s = device_memory_stats(device_id)
    # bytes_limit would report pool CAPACITY, not reservations — fall back
    # to allocated instead
    if "bytes_reserved" in s:
        return int(s["bytes_reserved"])
    return memory_allocated(device_id)
