"""Place / device model.

Analog of the reference's ``phi::Place`` + ``AllocationType`` enum
(paddle/phi/common/place.h:30) and ``DeviceManager``
(paddle/phi/backends/device_manager.h:134). On TPU the device axis collapses
to {cpu, tpu}: XLA owns streams/contexts, so a Place here is (kind, index)
used for `paddle.set_device` parity and for pinning host staging buffers.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "set_device", "get_device",
    "device_count", "current_place", "is_compiled_with_tpu", "synchronize",
    "local_devices", "default_backend",
]


class Place:
    """A (kind, index) device identifier. kind in {"cpu", "tpu", "gpu"}."""

    __slots__ = ("kind", "index")

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)

    def __hash__(self):
        return hash((self.kind, self.index))

    def is_cpu_place(self):
        return self.kind == "cpu"

    def is_tpu_place(self):
        return self.kind == "tpu"

    @property
    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform == _JAX_PLATFORM.get(self.kind, self.kind)]
        if not devs:
            devs = jax.devices()
        return devs[self.index % len(devs)]


_JAX_PLATFORM = {"tpu": "tpu", "cpu": "cpu", "gpu": "gpu"}


def CPUPlace(index: int = 0) -> Place:
    return Place("cpu", index)


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


@functools.lru_cache(maxsize=None)
def default_backend() -> str:
    return jax.default_backend()


_current_place: Optional[Place] = None


def set_device(device: str) -> Place:
    """``paddle.set_device``-style: "tpu", "tpu:0", "cpu"."""
    global _current_place
    if ":" in device:
        kind, idx = device.split(":", 1)
        place = Place(kind, int(idx))
    else:
        place = Place(device, 0)
    _current_place = place
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.kind}:{p.index}"


def current_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = Place(default_backend(), 0)
    return _current_place


def device_count(kind: Optional[str] = None) -> int:
    kind = kind or current_place().kind
    return len([d for d in jax.devices() if d.platform == _JAX_PLATFORM.get(kind, kind)]) or len(jax.devices())


def local_devices():
    return jax.local_devices()


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def synchronize() -> None:
    """Block until all queued device work completes (cudaDeviceSynchronize analog).

    XLA dispatch is async; this drains it by blocking on a trivial transfer.
    """
    (jax.device_put(0) + 0).block_until_ready()
