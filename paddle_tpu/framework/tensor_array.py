"""TensorArray — dynamic tensor list (N5 gap; reference LoDTensorArray).

Reference analog: the LoDTensorArray variable type plus the array ops
(paddle/fluid/operators/array_operator.h, python surface
python/paddle/tensor/array.py: array_length/array_read/array_write/
create_array). Used by while-loop style decoding and RNN unrolls.

TPU-native form: an eager Python list of Tensors with the paddle API on
top. Under jit tracing a TensorArray works whenever its length is
trace-static (the usual case: bounded unrolls); for fully dynamic lengths
inside one compiled graph, use lax.scan-style loops (jit/to_static) — the
same boundary the reference draws between LoDTensorArray and while_op.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from paddle_tpu.framework.tensor import Tensor

__all__ = ["TensorArray", "create_array", "array_length", "array_read",
           "array_write"]


class TensorArray:
    """Append/read/write list of same-rank Tensors with stack/concat exits."""

    def __init__(self, values: Optional[List[Tensor]] = None):
        self._items: List[Tensor] = list(values or [])

    # -- paddle array API ---------------------------------------------------
    def append(self, x) -> "TensorArray":
        self._items.append(_as_tensor(x))
        return self

    def write(self, index: int, x) -> "TensorArray":
        index = int(index)
        if index == len(self._items):
            self._items.append(_as_tensor(x))
        elif index < len(self._items):
            self._items[index] = _as_tensor(x)
        else:  # paddle semantics: grow with zeros-like up to index
            filler = _as_tensor(x)
            while len(self._items) < index:
                self._items.append(Tensor(jnp.zeros_like(filler._value)))
            self._items.append(filler)
        return self

    def read(self, index: int) -> Tensor:
        return self._items[int(index)]

    def pop(self, index: int = -1) -> Tensor:
        return self._items.pop(int(index))

    def __len__(self) -> int:
        return len(self._items)

    def __getitem__(self, index):
        return self._items[index]

    def __iter__(self):
        return iter(self._items)

    def stack(self, axis: int = 0) -> Tensor:
        import paddle_tpu as paddle
        return paddle.stack(list(self._items), axis=axis)

    def concat(self, axis: int = 0) -> Tensor:
        import paddle_tpu as paddle
        return paddle.concat(list(self._items), axis=axis)


def _as_tensor(x) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def create_array(dtype="float32", initialized_list=None) -> TensorArray:
    """python/paddle/tensor/array.py:create_array analog."""
    return TensorArray([_as_tensor(v) for v in (initialized_list or [])])


def array_length(array: TensorArray) -> Tensor:
    return Tensor(jnp.asarray(len(array)))


def array_read(array: TensorArray, i) -> Tensor:
    return array.read(int(i.numpy()) if isinstance(i, Tensor) else int(i))


def array_write(x, i, array: Optional[TensorArray] = None) -> TensorArray:
    if array is None:
        array = TensorArray()
    array.write(int(i.numpy()) if isinstance(i, Tensor) else int(i), x)
    return array
