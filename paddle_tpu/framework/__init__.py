from paddle_tpu.framework import dtype, device, random  # noqa: F401
