"""The eager Tensor.

Analog of the reference's ``phi::DenseTensor`` (paddle/phi/core/dense_tensor.h:37)
+ pybind eager Tensor object (paddle/fluid/pybind/eager.cc) + ``AutogradMeta``
(paddle/fluid/eager/autograd_meta.h:61) — collapsed into one Python class that
wraps a ``jax.Array`` (or a tracer, when executing under ``jit``/``to_static``).

XLA owns device memory and layout; what this class owns is autograd metadata
(stop_gradient / grad / grad node edge), naming, and the paddle-style method
surface (patched on by ``paddle_tpu.ops``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework import dtype as dtypes

__all__ = ["Tensor", "Parameter", "to_tensor", "is_tensor"]


class Tensor:
    __slots__ = (
        "_value", "stop_gradient", "_grad", "_grad_node", "_out_index",
        "name", "persistable", "_placements", "_process_mesh", "_hooks",
        "_dist_pad", "__weakref__",
    )

    # make numpy prefer our __r*__ ops over elementwise np ops
    __array_priority__ = 100

    def __init__(self, value: Any, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        elif isinstance(value, (np.ndarray, np.generic, int, float, bool, list, tuple)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self._placements = None
        self._process_mesh = None
        self._hooks = None  # leaf gradient hooks (register_hook)
        # uneven dist tensors: physical value is tile-padded; this records
        # the LOGICAL global shape (pad-and-mask uneven sharding support)
        self._dist_pad = None

    # -- raw value access ---------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        if self._dist_pad is not None:
            return tuple(self._dist_pad)
        return tuple(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def dtype(self):
        return jnp.dtype(self._value.dtype)

    @property
    def place(self):
        from paddle_tpu.framework.device import Place
        devs = getattr(self._value, "devices", None)
        if devs:
            d = next(iter(devs())) if callable(devs) else next(iter(devs))
            return Place(d.platform, d.id)
        from paddle_tpu.framework.device import current_place
        return current_place()

    def numpy(self) -> np.ndarray:
        # the single concretization choke point (__int__/__float__/item/
        # tolist/__array__/__bool__-fallback all land here): under a
        # to_static guard-specialization context this records the value
        # (probe) or substitutes the baked one (replay) — see
        # jit/conc_capture.py
        from paddle_tpu.jit import conc_capture
        if conc_capture.active() is not None:
            r = conc_capture.resolve_numpy(self._logical_value())
            if r is not None:
                return r
        return np.asarray(self._logical_value())

    def _logical_value(self):
        """The unpadded (logical) value; identical to ``_value`` except for
        uneven-sharded dist tensors, whose physical storage is tile-padded
        (gathers the pad off — the cost of computing on an uneven view)."""
        if self._dist_pad is None:
            return self._value
        idx = tuple(slice(0, s) for s in self._dist_pad)
        return self._value[idx]

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, d) -> "Tensor":
        from paddle_tpu import ops
        return ops.cast(self, d)

    def cast(self, d) -> "Tensor":
        return self.astype(d)

    # -- autograd -----------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g):
        if g is not None and not isinstance(g, Tensor):
            g = Tensor(g)
        self._grad = g

    def _accumulate_grad(self, g_value) -> None:
        """Leaf gradient accumulation (GradNodeAccumulation analog,
        paddle/fluid/eager/accumulation/accumulation_node.h)."""
        if isinstance(g_value, Tensor):
            g_value = g_value._logical_value()
        if self._dist_pad is not None and tuple(
                jnp.shape(g_value)) == tuple(self._dist_pad):
            # uneven-sharded param: store the grad PADDED like the param's
            # physical buffer so optimizer updates are shape-consistent
            # (pad rows get zero grads and therefore never change)
            pads = [(0, p - l) for p, l in zip(self._value.shape,
                                               self._dist_pad)]
            g_value = jnp.pad(g_value, pads)
            if hasattr(self._value, "sharding"):
                g_value = jax.device_put(g_value, self._value.sharding)
        if self._grad is None:
            self._grad = Tensor(g_value, stop_gradient=True)
        else:
            self._grad = Tensor(self._grad._value + g_value, stop_gradient=True)
        if self._dist_pad is not None:
            self._grad._dist_pad = self._dist_pad

    def backward(self, grad_tensor=None, retain_graph: bool = False) -> None:
        from paddle_tpu.autograd import tape
        tape.backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self) -> None:
        self._grad = None

    def clear_gradient(self) -> None:  # paddle alias
        self._grad = None

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        t._placements = self._placements
        t._process_mesh = self._process_mesh
        t._dist_pad = self._dist_pad
        return t

    def clone(self) -> "Tensor":
        from paddle_tpu import ops
        return ops.assign(self)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def register_hook(self, hook):
        """Register a gradient hook: ``hook(grad) -> new_grad | None``, run
        when this tensor's gradient is computed during backward.

        Leaf tensors fire once with the fully-accumulated gradient (before
        it lands in ``.grad``); non-leaf tensors fire on the cotangent
        before it enters the producing op's vjp, so a returned replacement
        changes all upstream gradients. Returns a removable handle.
        (tensor_patch_methods.py register_hook +
        GradNodeBase::RegisterGradientHook, grad_node_info.h:197 analog.)
        """
        if self.stop_gradient and self._grad_node is None:
            raise RuntimeError(
                "cannot register a gradient hook on a tensor with "
                "stop_gradient=True")

        class _Handle:
            def __init__(self, owner, key):
                self._owner = owner
                self._key = key

            def remove(self):
                o, k = self._owner, self._key
                if isinstance(o, dict):
                    o.pop(k, None)
                elif k in o:  # list of entries; remove THIS registration only
                    o.remove(k)

        if self._grad_node is not None:
            # non-leaf: hook lives on the producing node's output slot.
            # Wrap in a unique entry so removing one handle never unhooks a
            # second registration of the same callable.
            entry = lambda g, _fn=hook: _fn(g)  # noqa: E731
            self._grad_node.add_hook(self._out_index, entry)
            slot = self._grad_node.hooks[self._out_index]
            return _Handle(slot, entry)
        if self._hooks is None:
            self._hooks = {}
        key = len(self._hooks)
        while key in self._hooks:
            key += 1
        self._hooks[key] = hook
        return _Handle(self._hooks, key)

    # -- mutation (optimizer fast path; breaks no autograd history) ---------
    def _set_value(self, new_value) -> None:
        if isinstance(new_value, Tensor):
            new_value = new_value._value
        self._value = new_value

    def copy_(self, other) -> "Tensor":
        self._set_value(other)
        return self

    def set_value(self, other) -> None:
        self._set_value(jnp.asarray(other) if not isinstance(other, (Tensor,)) else other)

    def block_until_ready(self) -> "Tensor":
        if hasattr(self._value, "block_until_ready"):
            self._value.block_until_ready()
        return self

    # -- dist metadata (DistTensor analog, set by distributed.shard_tensor) --
    @property
    def placements(self):
        return self._placements

    @property
    def process_mesh(self):
        return self._process_mesh

    @property
    def is_dist(self) -> bool:
        return self._placements is not None

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        if not self.shape:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        try:
            data = np.asarray(self._value)
            return (f"Tensor(shape={list(self.shape)}, dtype={self.dtype.name}"
                    f"{grad_info},\n       {data})")
        except Exception:
            return f"Tensor(shape={list(self.shape)}, dtype={self.dtype.name}{grad_info}, traced)"

    def __bool__(self):
        v = self._value
        if isinstance(v, jax.core.Tracer):
            # inside a to_static capture, data-dependent bools are FORCED
            # per explored path (lax.cond capture) instead of erroring —
            # see jit/cond_capture.py
            from paddle_tpu.jit.cond_capture import resolve_traced_bool
            r = resolve_traced_bool(v)
            if r is not None:
                return r
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # arithmetic / indexing methods are patched on by paddle_tpu.ops.methods


class Parameter(Tensor):
    """A trainable Tensor (stop_gradient=False, persistable)."""

    __slots__ = ("trainable", "optimize_attr")

    def __init__(self, value, name: Optional[str] = None, trainable: bool = True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """``paddle.to_tensor`` analog."""
    if isinstance(data, Tensor):
        v = data._value
    else:
        v = data
    d = dtypes.convert_dtype(dtype)
    if isinstance(v, (int, float, bool, list, tuple, np.ndarray, np.generic)):
        arr = np.asarray(v)
        if d is None and arr.dtype == np.float64:
            d = dtypes.convert_dtype(_default_float())
        v = jnp.asarray(arr, dtype=d)
    elif d is not None and jnp.dtype(v.dtype) != d:
        v = v.astype(d)
    if place is not None:
        v = jax.device_put(v, place.jax_device)
    return Tensor(v, stop_gradient=stop_gradient)


def _default_float():
    from paddle_tpu.flags import flags
    return flags.default_dtype
